"""Continuous mid-scale parity gate (VERDICT round-1 item #4).

Runs the faithful greedy analyzer and the TPU engine on the same
200-broker / 5000-partition RandomCluster fixture and enforces the two
claims BASELINE.md makes at scale:

* quality: TPU violation score <= greedy's, and
* speed: TPU wall-clock < greedy / 10 (on an accelerator; pass
  ``--ratio`` to relax when profiling on CPU).

Persists the measurement as ``PARITY_GATE.json`` at the repo root (next to
the driver's ``BENCH_r*.json``) so the 552x/35%-better class of claims is
regression-tested, not folklore.  Exit code 0 = both gates hold.

Usage: python benchmarks/parity_gate.py [--brokers 200] [--partitions 5000]
       [--ratio 10] [--out PARITY_GATE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import time


def run(num_brokers: int = 200, num_partitions: int = 5000,
        min_speedup: float = 10.0, seed: int = 42, out: str | None = None):
    from cruise_control_tpu.utils.jit_cache import enable as _jc

    _jc()
    from cruise_control_tpu.analyzer.goal_optimizer import (
        GoalOptimizer,
        make_goals,
    )
    from cruise_control_tpu.analyzer.tpu_optimizer import TpuGoalOptimizer
    from cruise_control_tpu.analyzer.verifier import (
        verify_result,
        violation_score,
    )
    from cruise_control_tpu.models.generators import random_cluster

    state = random_cluster(
        seed=seed, num_brokers=num_brokers,
        num_racks=max(4, num_brokers // 10),
        num_partitions=num_partitions, mean_utilization=0.4,
    )
    goals = make_goals()

    t0 = time.perf_counter()
    greedy = GoalOptimizer(goals).optimize(state)
    t_greedy = time.perf_counter() - t0
    s_greedy = violation_score(greedy.final_state, goals)

    tpu_opt = TpuGoalOptimizer()
    # warm-up on a distinct seed so compile time never pollutes the gate
    tpu_opt.optimize(random_cluster(
        seed=seed + 1, num_brokers=num_brokers,
        num_racks=max(4, num_brokers // 10),
        num_partitions=num_partitions, mean_utilization=0.4,
    ))
    t0 = time.perf_counter()
    tpu = tpu_opt.optimize(state)
    t_tpu = time.perf_counter() - t0
    verify_result(state, tpu, goals)
    s_tpu = violation_score(tpu.final_state, goals)

    result = {
        "fixture": {"brokers": num_brokers, "partitions": num_partitions,
                    "seed": seed},
        "greedy": {"wallclock_s": round(t_greedy, 2),
                   "violation_score": s_greedy},
        "tpu": {"wallclock_s": round(t_tpu, 2), "violation_score": s_tpu},
        "speedup": round(t_greedy / max(t_tpu, 1e-9), 1),
        "quality_gate": bool(s_tpu <= s_greedy),
        "speed_gate": bool(t_tpu * min_speedup < t_greedy),
        "min_speedup": min_speedup,
    }
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=1)
    return result


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--brokers", type=int, default=200)
    ap.add_argument("--partitions", type=int, default=5000)
    ap.add_argument("--ratio", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument(
        "--out",
        default=os.path.join(os.path.dirname(__file__), "..",
                             "PARITY_GATE.json"),
    )
    args = ap.parse_args()
    result = run(args.brokers, args.partitions, args.ratio, args.seed,
                 os.path.abspath(args.out))
    print(json.dumps(result))
    return 0 if (result["quality_gate"] and result["speed_gate"]) else 1


if __name__ == "__main__":
    raise SystemExit(main())
