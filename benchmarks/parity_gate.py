"""Continuous mid-scale parity gate (VERDICT round-1 item #4).

Runs the faithful greedy analyzer and the TPU engine on the same
200-broker / 5000-partition RandomCluster fixture and enforces the two
claims BASELINE.md makes at scale:

* quality: TPU violation score <= greedy's, and
* speed: TPU wall-clock < greedy / 10 (on an accelerator; pass
  ``--ratio`` to relax when profiling on CPU).

Persists the measurement as ``PARITY_GATE.json`` at the repo root (next to
the driver's ``BENCH_r*.json``) so the 552x/35%-better class of claims is
regression-tested, not folklore.  Exit code 0 = both gates hold.

Usage: python benchmarks/parity_gate.py [--brokers 200] [--partitions 5000]
       [--ratio 10] [--out PARITY_GATE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import time


def _fixture(seed, num_brokers, num_partitions, num_racks, mean_util):
    from cruise_control_tpu.models.generators import random_cluster

    return random_cluster(
        seed=seed, num_brokers=num_brokers,
        num_racks=num_racks or max(4, num_brokers // 10),
        num_partitions=num_partitions,
        mean_utilization=mean_util,
    )


def run(num_brokers: int = 200, num_partitions: int = 5000,
        min_speedup: float = 10.0, seed: int = 42, out: str | None = None,
        num_racks: int = 0, mean_util: float = 0.4, phase: str = "both"):
    """``phase``: "both" (default), or split the measurement — "greedy"
    runs the baseline on the CPU backend only (no accelerator claim; the
    34-minute mid-scale oracle can run while the chip does other work) and
    persists its half to ``out``; "tpu" reads that half back, runs the
    engine, and writes the merged gates."""
    import jax

    if phase == "greedy":
        jax.config.update("jax_platforms", "cpu")
    from cruise_control_tpu.utils.jit_cache import enable as _jc

    _jc()
    from cruise_control_tpu.analyzer.goal_optimizer import (
        GoalOptimizer,
        make_goals,
    )
    from cruise_control_tpu.analyzer.tpu_optimizer import TpuGoalOptimizer
    from cruise_control_tpu.analyzer.verifier import (
        verify_result,
        violation_score,
    )

    fixture = {"brokers": num_brokers, "partitions": num_partitions,
               "seed": seed, "racks": num_racks, "mean_util": mean_util}
    state = _fixture(seed, num_brokers, num_partitions, num_racks, mean_util)
    goals = make_goals()

    if phase == "tpu":
        with open(out) as f:
            result = json.load(f)
        assert result["fixture"] == fixture, (
            f"greedy half measured a different fixture: "
            f"{result['fixture']} != {fixture}"
        )
        t_greedy = result["greedy"]["wallclock_s"]
        s_greedy = result["greedy"]["violation_score"]
    else:
        t0 = time.perf_counter()
        greedy = GoalOptimizer(goals).optimize(state)
        t_greedy = time.perf_counter() - t0
        s_greedy = violation_score(greedy.final_state, goals)
        result = {
            "fixture": fixture,
            "greedy": {"wallclock_s": round(t_greedy, 2),
                       "violation_score": s_greedy},
        }
        if phase == "greedy":
            if out:
                with open(out, "w") as f:
                    json.dump(result, f, indent=1)
            return result

    tpu_opt = TpuGoalOptimizer()
    # warm-up on a distinct seed so compile time never pollutes the gate
    tpu_opt.optimize(_fixture(seed + 1, num_brokers, num_partitions,
                              num_racks, mean_util))
    t0 = time.perf_counter()
    tpu = tpu_opt.optimize(state)
    t_tpu = time.perf_counter() - t0
    verify_result(state, tpu, goals)
    s_tpu = violation_score(tpu.final_state, goals)

    # drive-loop pipelining gate: the default (pipelined) engine must
    # produce a bit-identical plan to serial round-trips
    import dataclasses as _dc

    from cruise_control_tpu.analyzer.tpu_optimizer import TpuSearchConfig

    serial = TpuGoalOptimizer(
        config=_dc.replace(TpuSearchConfig(), pipeline_depth=0)
    ).optimize(state)

    def _tuples(r):
        return [
            (a.action_type, a.partition, a.slot, a.source_broker,
             a.dest_broker, a.dest_slot)
            for a in r.actions
        ]

    pipeline_identical = _tuples(serial) == _tuples(tpu)

    result.update({
        "tpu": {"wallclock_s": round(t_tpu, 2), "violation_score": s_tpu},
        "speedup": round(t_greedy / max(t_tpu, 1e-9), 1),
        "quality_gate": bool(s_tpu <= s_greedy),
        "speed_gate": bool(t_tpu * min_speedup < t_greedy),
        "min_speedup": min_speedup,
        # which backend the TPU half actually ran on — a CPU-backend
        # refresh must not masquerade as an accelerator measurement
        "tpu_platform": jax.default_backend(),
        "pipeline_depth": TpuSearchConfig().pipeline_depth,
        "pipeline_identical": pipeline_identical,
    })
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=1)
    return result


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--brokers", type=int, default=200)
    ap.add_argument("--partitions", type=int, default=5000)
    ap.add_argument("--racks", type=int, default=0,
                    help="0 = max(4, brokers/10)")
    ap.add_argument("--mean-util", type=float, default=0.4)
    ap.add_argument("--ratio", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--phase", choices=("both", "greedy", "tpu"),
                    default="both")
    ap.add_argument(
        "--out",
        default=os.path.join(os.path.dirname(__file__), "..",
                             "PARITY_GATE.json"),
    )
    args = ap.parse_args()
    result = run(args.brokers, args.partitions, args.ratio, args.seed,
                 os.path.abspath(args.out), num_racks=args.racks,
                 mean_util=args.mean_util, phase=args.phase)
    print(json.dumps(result))
    if args.phase == "greedy":
        return 0
    return 0 if (
        result["quality_gate"] and result["speed_gate"]
        and result["pipeline_identical"]
    ) else 1


if __name__ == "__main__":
    raise SystemExit(main())
