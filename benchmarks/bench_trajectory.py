"""Fold every committed ``BENCH_r*.json`` into the machine-readable perf
trajectory (``BENCH_TRAJECTORY.md``).

The driver bench has emitted one JSON record per round since round 1, but
the trajectory existed only as N loose files — nothing showed the
north-star seconds, the greedy baseline, and every overhead gate side by
side, and nothing asserted that the latest round still holds its gates
without re-running the bench.  This tool renders the committed table
(regenerate with ``PYTHONPATH=. python benchmarks/bench_trajectory.py``)
and exposes the parsed rounds + per-gate verdicts for the tier-1 test
(``tests/test_bench_trajectory.py``), which pins BOTH: the table is in
sync with the artifacts, and the newest round's gates all pass.

Round 1–5 artifacts are harness wrappers (``{"n", "cmd", "tail"}``) whose
metric line is embedded in the captured tail; round 6+ artifacts are the
bench's JSON record directly.  Both parse here.
"""

from __future__ import annotations

import json
import pathlib
import re
from typing import Dict, List, Optional, Tuple

ROOT = pathlib.Path(__file__).resolve().parent.parent
OUTPUT = ROOT / "BENCH_TRAJECTORY.md"

#: overhead gates: metric key → (mode, budget_pct).  ``max`` is one-sided
#: (a negative measurement is noise in the subsystem's favor); ``absmax``
#: bounds both directions (the precompute gate's ±1% contract).
GATES: Dict[str, Tuple[str, float]] = {
    "tracing_overhead_pct": ("max", 1.0),
    "recorder_overhead_pct": ("max", 2.0),
    "events_overhead_pct": ("max", 2.0),
    "checkpoint_overhead_pct": ("max", 1.0),
    "precompute_overhead_pct": ("absmax", 1.0),
    "replan_overhead_pct": ("max", 1.0),
    "slo_overhead_pct": ("max", 1.0),
    "validation_overhead_pct": ("max", 1.0),
    "profiler_overhead_pct": ("max", 1.0),
    "mesh_overhead_pct": ("max", 1.0),
    "host_profiler_overhead_pct": ("max", 1.0),
    "lock_witness_overhead_pct": ("max", 1.0),
    # a ratio, not a pct: the 64-future batched what-if sweep must cost
    # < 2x one plan search (ISSUE 16)
    "whatif_batch_ratio": ("max", 2.0),
}

#: the north-star wall-clock ceiling (round-6 acceptance, held since)
NORTHSTAR_MAX_S = 0.50
#: the TPU engine must beat greedy by at least this factor
VS_BASELINE_MIN = 2.0
#: settled warm replans must stay at least this much faster than cold
REPLAN_SETTLE_MIN = 10.0
#: sharded search: min per-device work speedup across scales (round 20;
#: plans must also stay bit-identical — folded into the same verdict)
SHARDED_WORK_MIN = 4.0

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def _parse_artifact(path: pathlib.Path) -> Optional[dict]:
    doc = json.loads(path.read_text())
    if "metric" in doc:
        return doc
    # rounds 1–5: the harness wrapper; the record is the tail's last
    # {"metric": ...} line
    lines = [ln for ln in doc.get("tail", "").splitlines()
             if ln.startswith('{"metric"')]
    if not lines:
        return None
    try:
        return json.loads(lines[-1])
    except ValueError:
        return None


def load_rounds(root: pathlib.Path = ROOT) -> List[Tuple[int, dict]]:
    """[(round, bench record), ...] ascending; unparseable rounds are
    skipped (they never carried a record)."""
    out = []
    for path in sorted(root.glob("BENCH_r*.json")):
        m = _ROUND_RE.search(path.name)
        if not m:
            continue
        rec = _parse_artifact(path)
        if rec is not None:
            out.append((int(m.group(1)), rec))
    return sorted(out)


def gate_verdicts(rec: dict) -> Dict[str, Tuple[float, bool]]:
    """{gate: (measured, ok)} for every gate the round carries."""
    out: Dict[str, Tuple[float, bool]] = {}
    for key, (mode, budget) in GATES.items():
        v = rec.get(key)
        if v is None:
            continue
        ok = abs(v) <= budget if mode == "absmax" else v <= budget
        out[key] = (float(v), ok)
    v = rec.get("value")
    if v is not None:
        out["northstar_s"] = (float(v), float(v) <= NORTHSTAR_MAX_S)
    v = rec.get("vs_baseline")
    if v is not None:
        out["vs_baseline"] = (float(v), float(v) >= VS_BASELINE_MIN)
    drift = rec.get("replan_after_drift")
    if isinstance(drift, dict) and drift.get("settle_speedup") is not None:
        s = float(drift["settle_speedup"])
        out["replan_settle_speedup"] = (s, s >= REPLAN_SETTLE_MIN)
    soak = rec.get("soak_smoke")
    if isinstance(soak, dict) and soak.get("wall_s") is not None:
        w = float(soak["wall_s"])
        out["soak_smoke"] = (
            w,
            bool(soak.get("all_ok"))
            and w <= float(soak.get("budget_s", 120.0)),
        )
    sharded = rec.get("sharded_scaling")
    if isinstance(sharded, dict) \
            and sharded.get("per_device_work_speedup") is not None:
        s = float(sharded["per_device_work_speedup"])
        out["sharded_scaling"] = (
            s,
            s >= float(sharded.get("gate", SHARDED_WORK_MIN))
            and bool(sharded.get("plan_identical"))
            and bool(sharded.get("ok")),
        )
    return out


def _cell(verdicts: Dict[str, Tuple[float, bool]], key: str) -> str:
    if key not in verdicts:
        return "—"
    value, ok = verdicts[key]
    return f"{value:g}" if ok else f"**{value:g} ✗**"


def render(rounds: List[Tuple[int, dict]]) -> str:
    cols = [
        ("northstar_s", f"northstar s (≤{NORTHSTAR_MAX_S:g})"),
        ("vs_baseline", f"vs_baseline (≥{VS_BASELINE_MIN:g}×)"),
        ("tracing_overhead_pct", "tracing % (≤1)"),
        ("recorder_overhead_pct", "recorder % (≤2)"),
        ("events_overhead_pct", "events % (≤2)"),
        ("checkpoint_overhead_pct", "checkpoint % (≤1)"),
        ("precompute_overhead_pct", "precompute % (±1)"),
        ("replan_overhead_pct", "replan % (≤1)"),
        ("slo_overhead_pct", "slo % (≤1)"),
        ("validation_overhead_pct", "validation % (≤1)"),
        ("profiler_overhead_pct", "profiler % (≤1)"),
        ("mesh_overhead_pct", "mesh % (≤1)"),
        ("host_profiler_overhead_pct", "host prof % (≤1)"),
        ("lock_witness_overhead_pct", "lock witness % (≤1)"),
        ("whatif_batch_ratio", "whatif batch × (<2)"),
        ("replan_settle_speedup", f"settle × (≥{REPLAN_SETTLE_MIN:g})"),
        ("soak_smoke", "soak smoke s (green, ≤budget)"),
        ("sharded_scaling",
         f"shard work × (≥{SHARDED_WORK_MIN:g}, plans =)"),
    ]
    lines = [
        "# Perf trajectory — every committed driver-bench round",
        "",
        "Generated by `PYTHONPATH=. python benchmarks/bench_trajectory.py`"
        " from the committed `BENCH_r*.json` artifacts; "
        "`tests/test_bench_trajectory.py` keeps this table in sync and "
        "asserts the LATEST round's gates all hold.  `—` = the gate did "
        "not exist that round; a failing cell renders bold with ✗.  "
        "North-star metric: `rebalance_plan_wallclock_50b_1000p` "
        "(seconds, best-of; `vs_baseline` = greedy wall-clock / TPU "
        "wall-clock on identical fixtures — the committed `baseline_s` "
        "makes swings attributable, see `BENCH_r06.json` notes).",
        "",
        "| round | " + " | ".join(label for _, label in cols) + " |",
        "|---" * (len(cols) + 1) + "|",
    ]
    for rnd, rec in rounds:
        verdicts = gate_verdicts(rec)
        cells = [_cell(verdicts, key) for key, _ in cols]
        lines.append(f"| r{rnd:02d} | " + " | ".join(cells) + " |")
    lines += [
        "",
        "Round-5's `vs_baseline` spike (53.9×) was the greedy-baseline "
        "regression root-caused and fixed in round 6 "
        "(`VERDICT.md` r5 §1), not an engine speedup — the reason the "
        "`baseline_s` column exists in the record.",
        "",
    ]
    return "\n".join(lines)


def main() -> int:
    rounds = load_rounds()
    OUTPUT.write_text(render(rounds))
    latest, rec = rounds[-1]
    verdicts = gate_verdicts(rec)
    failed = sorted(k for k, (_, ok) in verdicts.items() if not ok)
    print(f"wrote {OUTPUT.name}: {len(rounds)} rounds, latest r{latest}, "
          f"{len(verdicts)} gates, "
          + (f"FAILED: {failed}" if failed else "all gates pass"))
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
