"""Per-kernel device budget of the resident scan step (round-4 item #1).

Traces ONE warm scan call (``--steps`` while-loop steps) at north-star
shapes with ``jax.profiler``, parses the device track of the Chrome-trace
the TPU runtime emits (per-kernel ``device_duration_ps``,
``bytes_accessed``, ``model_flops``, ``hlo_category``), and prints a
per-step kernel budget:

  * kernels/step, device-busy time/step, wall time/step
  * bytes accessed/step  → HBM-bandwidth floor at the chip's peak
  * model flops/step     → compute floor
  * top kernels by total device time, with per-step count/time/bytes

This is the number that decides whether the ~28 ms step has fusion
headroom or sits on a hardware floor (round-2 ask, round-3 VERDICT weak
#1).  Output: human table on stderr, one JSON document on stdout —
commit it as ``benchmarks/KERNEL_BUDGET_r*.json``.

Usage:
    PYTHONPATH=.:/root/.axon_site python benchmarks/kernel_budget.py \
        [--brokers 10000] [--partitions 1000000] [--steps 64]
"""

from __future__ import annotations

import argparse
import dataclasses
import glob
import gzip
import json
import os
import sys
import time

# TPU v5e (v5 lite) datasheet peaks — the roofline denominators
HBM_BYTES_PER_S = 819e9
PEAK_F32_FLOPS = 98.3e12  # MXU bf16 is 197; the scoring path is f32


def sync(x):
    import numpy as np

    import jax

    leaves = jax.tree_util.tree_leaves(x)
    for v in leaves:
        if hasattr(v, "block_until_ready"):
            v.block_until_ready()
    # the axon relay can report ready early; a concrete fetch is honest
    np.asarray(jax.numpy.ravel(leaves[0])[0])


def newest_trace(trace_dir: str) -> str:
    paths = glob.glob(
        os.path.join(trace_dir, "plugins", "profile", "*", "*.trace.json.gz")
    )
    if not paths:
        raise FileNotFoundError(f"no trace under {trace_dir}")
    return max(paths, key=os.path.getmtime)


def parse_device_kernels(trace_path: str):
    """→ kernel rows: one per HLO name, aggregated over the device "XLA
    Ops" track with SELF-time accounting.

    Control-flow region events (``while.*``/``cond.*``) nest their body
    kernels inside their interval on the same thread, so naive sums count
    every nanosecond (and byte) twice.  Events nest strictly; a stack
    walk attributes to each event its duration minus its children's
    (self time) and, for bytes/flops, leaf values only (region events'
    counters re-aggregate their bodies)."""
    with gzip.open(trace_path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    device_pids = {
        e["pid"]
        for e in events
        if e.get("ph") == "M"
        and e.get("name") == "process_name"
        and str(e.get("args", {}).get("name", "")).startswith("/device:")
    }
    per_thread: dict = {}
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        if "hlo_category" not in e.get("args", {}):
            continue  # umbrella program event, not a kernel
        per_thread.setdefault((e["pid"], e["tid"]), []).append(e)

    agg: dict = {}

    def account(e, child_time_us: float, is_region: bool):
        args = e.get("args", {})
        dur_us = float(args.get("device_duration_ps", 0)) / 1e6
        row = agg.setdefault(
            e["name"],
            {
                "name": e["name"],
                "category": args.get("hlo_category", "?"),
                "count": 0,
                "time_us": 0.0,
                "total_time_us": 0.0,
                "bytes": 0,
                "flops": 0,
                "long_name": args.get("long_name", "")[:240],
            },
        )
        row["count"] += 1
        row["time_us"] += max(0.0, dur_us - child_time_us)
        row["total_time_us"] += dur_us
        if not is_region:
            row["bytes"] += int(args.get("raw_bytes_accessed",
                                         args.get("bytes_accessed", 0)))
            row["flops"] += int(args.get("model_flops", 0) or 0)

    for evs in per_thread.values():
        evs.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
        stack: list = []       # open events: (end_ts, event)
        child_time: list = []  # per open event: accumulated child device us

        def close_one():
            _end, ev = stack.pop()
            ct = child_time.pop()
            account(ev, ct, _is_region(ev))
            if child_time:  # this event is a child of the new stack top
                child_time[-1] += float(
                    ev["args"].get("device_duration_ps", 0)) / 1e6

        for e in evs:
            ts = e["ts"]
            while stack and ts >= stack[-1][0] - 1e-9:
                close_one()
            stack.append((ts + e.get("dur", 0.0), e))
            child_time.append(0.0)
        while stack:
            close_one()
    return list(agg.values())


def _is_region(e) -> bool:
    return e.get("args", {}).get("hlo_category") in (
        "while", "conditional", "fusion root"  # control-flow containers
    )


def main() -> None:
    from cruise_control_tpu.utils.jit_cache import enable as _jc

    _jc()
    ap = argparse.ArgumentParser()
    ap.add_argument("--brokers", type=int, default=10000)
    ap.add_argument("--partitions", type=int, default=1000000)
    ap.add_argument("--racks", type=int, default=200)
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--trace-dir", default="/tmp/cc_tpu_kernel_budget")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument(
        "--auction-rounds", type=int, default=-1,
        help="override tpu.search auction_rounds for the traced call "
        "(-1 = engine default, 0 = one round per alternate destination) — "
        "the r4 budget's item-2 sweep axis",
    )
    args = ap.parse_args()

    import jax

    import cruise_control_tpu.analyzer.tpu_optimizer as T
    from cruise_control_tpu.analyzer.context import AnalyzerContext
    from cruise_control_tpu.models.generators import random_cluster

    state = random_cluster(
        seed=5, num_brokers=args.brokers, num_racks=args.racks,
        num_partitions=args.partitions,
    )
    opt = T.TpuGoalOptimizer()
    ctx = AnalyzerContext(state)
    m = opt._device_model(ctx)
    ca = opt._constraint_arrays(ctx)
    P, S = ctx.num_partitions, ctx.max_rf
    B = ctx.num_brokers
    K, D = opt._pool_sizes(P, S, B)
    cfg = dataclasses.replace(
        opt.config,
        device_batch_per_step=int(min(max(B // 4, 32), 1024)),
    )
    if args.auction_rounds >= 0:
        cfg = dataclasses.replace(cfg, auction_rounds=args.auction_rounds)
    fn = T._cached_scan_fn(cfg, K, D, args.steps)

    print("warming (compile or cache load)...", file=sys.stderr)
    sync(fn(m, ca))

    os.makedirs(args.trace_dir, exist_ok=True)
    t0 = time.perf_counter()
    with jax.profiler.trace(args.trace_dir):
        packed, m2 = fn(m, ca)
        sync(packed)
    wall_s = time.perf_counter() - t0

    *_head, counts, done, _diag = T._fetch_scan_result(packed, args.steps)
    # The loop exits on (a) t == T, (b) convergence, or (c) slot-budget
    # overflow.  Only (a) makes "divide by T" correct, so PROVE the others
    # didn't happen rather than silently mis-divide the per-step budget:
    # (b) sets the done flag; (c) requires total commits beyond the
    # loop-condition threshold slots - M_.
    steps = int(args.steps)
    assert not done, "scan converged inside the traced call; budget would" \
        " mix converged no-op steps — rerun with fewer --steps"
    Q = max(1, cfg.moves_per_src)
    M_ = min(cfg.device_batch_per_step, (Q + 1) * B)
    slots = min(steps, max(1, cfg.repool_steps)) * M_
    total_commits = int(counts.sum())
    assert total_commits <= slots - M_, (
        f"scan hit its slot budget inside the traced call "
        f"({total_commits} commits, slots={slots}); fewer than "
        f"{steps} steps executed — rerun with fewer --steps"
    )

    rows = parse_device_kernels(newest_trace(args.trace_dir))
    rows.sort(key=lambda r: -r["time_us"])
    tot_time_us = sum(r["time_us"] for r in rows)
    tot_count = sum(r["count"] for r in rows)
    tot_bytes = sum(r["bytes"] for r in rows)
    tot_flops = sum(r["flops"] for r in rows)

    by_cat: dict = {}
    for r in rows:
        c = by_cat.setdefault(
            r["category"], {"count": 0, "time_us": 0.0, "bytes": 0}
        )
        c["count"] += r["count"]
        c["time_us"] += r["time_us"]
        c["bytes"] += r["bytes"]

    per_step = {
        "kernels": tot_count / steps,
        "device_busy_ms": tot_time_us / steps / 1e3,
        "wall_ms": wall_s * 1e3 / steps,
        "bytes_mb": tot_bytes / steps / 1e6,
        "model_gflops": tot_flops / steps / 1e9,
        "hbm_floor_ms": tot_bytes / steps / HBM_BYTES_PER_S * 1e3,
        "flops_floor_ms": tot_flops / steps / PEAK_F32_FLOPS * 1e3,
    }
    per_step["hbm_utilization_of_busy"] = (
        (tot_bytes / (tot_time_us / 1e6)) / HBM_BYTES_PER_S
        if tot_time_us else 0.0
    )

    hdr = (f"{'kernel':46s} {'cat':18s} {'n/step':>7s} {'us/step':>9s} "
           f"{'MB/step':>9s} {'GB/s':>7s}")
    print("\n" + hdr, file=sys.stderr)
    print("-" * len(hdr), file=sys.stderr)
    for r in rows[: args.top]:
        t_us = r["time_us"] / steps
        mb = r["bytes"] / steps / 1e6
        bw = (r["bytes"] / (r["time_us"] / 1e6) / 1e9) if r["time_us"] else 0
        print(
            f"{r['name'][:46]:46s} {r['category'][:18]:18s} "
            f"{r['count'] / steps:7.1f} {t_us:9.1f} {mb:9.3f} {bw:7.1f}",
            file=sys.stderr,
        )
    print(f"\nper step: {per_step['kernels']:.0f} kernels, "
          f"busy {per_step['device_busy_ms']:.2f} ms, "
          f"wall {per_step['wall_ms']:.2f} ms, "
          f"{per_step['bytes_mb']:.1f} MB "
          f"(HBM floor {per_step['hbm_floor_ms']:.2f} ms), "
          f"{per_step['model_gflops']:.1f} GF "
          f"(compute floor {per_step['flops_floor_ms']:.2f} ms)",
          file=sys.stderr)

    doc = {
        "fixture": {
            "brokers": args.brokers, "partitions": args.partitions,
            "racks": args.racks, "seed": 5, "K": K, "D": D,
            "steps_traced": steps,
            "auction_rounds": int(cfg.auction_rounds),
        },
        "hw": {"hbm_bytes_per_s": HBM_BYTES_PER_S,
               "peak_f32_flops": PEAK_F32_FLOPS, "chip": "v5e"},
        "per_step": {k: round(v, 4) for k, v in per_step.items()},
        "by_category": {
            k: {
                "count_per_step": round(v["count"] / steps, 2),
                "us_per_step": round(v["time_us"] / steps, 2),
                "mb_per_step": round(v["bytes"] / steps / 1e6, 4),
            }
            for k, v in sorted(by_cat.items(),
                               key=lambda kv: -kv[1]["time_us"])
        },
        "kernels": [
            {
                "name": r["name"],
                "category": r["category"],
                "count_per_step": round(r["count"] / steps, 2),
                "us_per_step": round(r["time_us"] / steps, 3),
                "mb_per_step": round(r["bytes"] / steps / 1e6, 5),
                "gbps": round(
                    r["bytes"] / (r["time_us"] / 1e6) / 1e9, 2
                ) if r["time_us"] else 0.0,
                "long_name": r["long_name"],
            }
            for r in rows
        ],
    }
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
