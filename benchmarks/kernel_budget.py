"""Per-kernel device budget of the resident scan step — offline edition.

Traces ONE warm scan call (``--steps`` while-loop steps) at the requested
shapes through the kernel observatory's single profiler entry point
(:mod:`cruise_control_tpu.telemetry.kernel_budget` — the parser, bucket
classifier, and artifact builder live THERE now; this script is the
steps-based offline driver) and prints a ``cc-tpu-kernel-budget/2``
artifact on stdout: per-step kernels / device-busy / bytes / HBM floor,
per-BUCKET self-time accounting (grid+top-k, auction rounds, move_vec
build, pool rebuild, long tail), and — with ``--devices N`` — the
per-device busy split and shard-skew ratio over a forced
``--xla_force_host_platform_device_count`` CPU mesh.

The artifact records the backend it was measured on: r04 numbers came
from a real v5e (``backend: "tpu"``, the device-event dialect with byte
counters); CPU refreshes parse the XLA:CPU thunk stream (wall-time
self-accounting, no byte counters) and are comparable to each other, not
to device-dialect rounds.

``--compare tests/budgets/kernel_budget.json`` gates the measured
per-bucket kernel counts against the pinned budget (exit 1 on growth
past the ceiling) — the same regression loop the tier-1 test runs on the
tiny fixture, available at any shape.

Usage:
    PYTHONPATH=. python benchmarks/kernel_budget.py \
        [--brokers 10000] [--partitions 1000000] [--steps 64] \
        [--devices 8] [--compare tests/budgets/kernel_budget.json]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time


def sync(x):
    import numpy as np

    import jax

    leaves = jax.tree_util.tree_leaves(x)
    for v in leaves:
        if hasattr(v, "block_until_ready"):
            v.block_until_ready()
    # the axon relay can report ready early; a concrete fetch is honest
    np.asarray(jax.numpy.ravel(leaves[0])[0])


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--brokers", type=int, default=10000)
    ap.add_argument("--partitions", type=int, default=1000000)
    ap.add_argument("--racks", type=int, default=200)
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--seed", type=int, default=5)
    ap.add_argument("--trace-dir", default="/tmp/cc_tpu_kernel_budget")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument(
        "--devices", type=int, default=0,
        help="shard the scan over an N-device mesh "
        "(--xla_force_host_platform_device_count on CPU) so the artifact "
        "carries per-device busy-ms and the shard-skew ratio",
    )
    ap.add_argument(
        "--device-batch", type=int, default=0,
        help="device_batch_per_step for the traced call (0 = the "
        "B/4-clamped auto heuristic).  Small skewed fixtures commit "
        "full batches every step and trip the slot-budget honesty "
        "assertion — give them headroom with a larger batch",
    )
    ap.add_argument(
        "--auction-rounds", type=int, default=-1,
        help="override tpu.search auction_rounds for the traced call "
        "(-1 = engine default, 0 = one round per alternate destination) — "
        "the r4 budget's item-2 sweep axis",
    )
    ap.add_argument(
        "--compare", default="",
        help="pinned budget JSON (tests/budgets/kernel_budget.json "
        "shape); exit 1 when per-bucket kernel counts grew past its "
        "ceiling",
    )
    ap.add_argument(
        "--mesh-out", default="",
        help="also parse the SAME trace through the mesh observatory "
        "(collectives / transfers / dispatch-gap attribution) and write "
        "the cc-tpu-mesh-budget/1 artifact here",
    )
    args = ap.parse_args()

    if args.devices > 1:
        # must land before the first jax import in this process
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()

    from cruise_control_tpu.utils.jit_cache import enable as _jc

    _jc()

    import numpy as np

    import jax

    import cruise_control_tpu.analyzer.tpu_optimizer as T
    from cruise_control_tpu.analyzer.context import AnalyzerContext
    from cruise_control_tpu.models.generators import random_cluster
    from cruise_control_tpu.telemetry import kernel_budget as kb

    state = random_cluster(
        seed=args.seed, num_brokers=args.brokers, num_racks=args.racks,
        num_partitions=args.partitions,
    )
    opt = T.TpuGoalOptimizer()
    ctx = AnalyzerContext(state)
    m = opt._device_model(ctx)
    ca = opt._constraint_arrays(ctx)
    P, S = ctx.num_partitions, ctx.max_rf
    B = ctx.num_brokers
    K, D = opt._pool_sizes(P, S, B)
    cfg = dataclasses.replace(
        opt.config,
        device_batch_per_step=(
            args.device_batch if args.device_batch > 0
            else int(min(max(B // 4, 32), 1024))
        ),
    )
    if args.auction_rounds >= 0:
        cfg = dataclasses.replace(cfg, auction_rounds=args.auction_rounds)
    mesh = None
    if args.devices > 1:
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[: args.devices]), ("search",)
        )
    fn = T._cached_scan_fn(cfg, K, D, args.steps, mesh)

    # cold pool-row tables: omitted (tables=None), so the scan entry
    # builds invalid placed zeros — sharded across the mesh when the
    # table carry is — and the traced call's first repool is the full
    # rebuild r04 measured.  donate_carry consumes the input model and
    # tables, so the traced call gets a fresh (bit-identical) upload.
    print("warming (compile or cache load)...", file=sys.stderr)
    sync(fn(m, ca, np.int32(args.steps)))
    m = opt._device_model(ctx)

    t0 = time.perf_counter()
    # the repo's ONE raw-profiler entry point (cclint profiler-discipline)
    with kb.profiler_session(args.trace_dir):
        packed, m2, _tab = fn(m, ca, np.int32(args.steps))
        sync(packed)
    wall_s = time.perf_counter() - t0

    *_head, counts, done, _diag = T._fetch_scan_result(packed, args.steps)
    # The loop exits on (a) t == T, (b) convergence, or (c) slot-budget
    # overflow.  Only (a) makes "divide by T" correct, so PROVE the others
    # didn't happen rather than silently mis-divide the per-step budget:
    # (b) sets the done flag; (c) requires total commits beyond the
    # loop-condition threshold slots - M_.
    steps = int(args.steps)
    assert not done, "scan converged inside the traced call; budget would" \
        " mix converged no-op steps — rerun with fewer --steps"
    Q = max(1, cfg.moves_per_src)
    M_ = min(cfg.device_batch_per_step, (Q + 1) * B)
    slots = min(steps, max(1, cfg.repool_steps)) * M_
    total_commits = int(counts.sum())
    assert total_commits <= slots - M_, (
        f"scan hit its slot budget inside the traced call "
        f"({total_commits} commits, slots={slots}); fewer than "
        f"{steps} steps executed — rerun with fewer --steps"
    )

    parsed = kb.parse_trace(kb.newest_trace(args.trace_dir))
    artifact = kb.build_artifact(
        parsed, units=steps, unit="step", source="benchmark",
        backend=jax.default_backend(),
        fixture={
            "brokers": args.brokers, "partitions": args.partitions,
            "racks": args.racks, "seed": args.seed, "K": K, "D": D,
            "steps_traced": steps, "devices": max(1, args.devices),
            "auction_rounds": int(cfg.auction_rounds),
        },
        top=max(args.top, 25),
    )
    artifact["per_unit"]["wall_ms"] = round(wall_s * 1e3 / steps, 4)

    rows = artifact["kernels"]
    hdr = (f"{'kernel':40s} {'bucket':14s} {'cat':14s} {'n/step':>7s} "
           f"{'us/step':>9s} {'MB/step':>9s}")
    print("\n" + hdr, file=sys.stderr)
    print("-" * len(hdr), file=sys.stderr)
    for r in rows[: args.top]:
        print(
            f"{r['name'][:40]:40s} {r['bucket'][:14]:14s} "
            f"{r['category'][:14]:14s} {r['count_per_unit']:7.1f} "
            f"{r['us_per_unit']:9.1f} {r['mb_per_unit']:9.3f}",
            file=sys.stderr,
        )
    pu = artifact["per_unit"]
    print(f"\nper step: {pu['kernels']:.0f} kernels, "
          f"busy {pu['device_busy_ms']:.2f} ms, "
          f"wall {pu['wall_ms']:.2f} ms, "
          f"{pu['bytes_mb']:.1f} MB "
          f"(HBM floor {pu['hbm_floor_ms']:.2f} ms); "
          f"buckets: "
          + ", ".join(f"{k}={v['us_per_unit'] / 1e3:.2f}ms"
                      for k, v in artifact["by_bucket"].items()),
          file=sys.stderr)
    dev = artifact["devices"]
    if dev["count"] > 1:
        print(f"shards: {dev['count']} devices, busy "
              + ", ".join(f"{k}={v:.2f}ms"
                          for k, v in dev["busy_ms"].items())
              + f", skew {dev['skew']}", file=sys.stderr)

    print(json.dumps(artifact))

    if args.mesh_out:
        from cruise_control_tpu.telemetry import mesh_budget as mb

        mparsed = mb.parse_mesh_trace(kb.newest_trace(args.trace_dir))
        mesh_art = mb.build_mesh_artifact(
            mparsed, units=steps, unit="step", source="benchmark",
            backend=jax.default_backend(), fixture=artifact["fixture"],
        )
        w = mesh_art["wall"]
        print(
            f"mesh: wall {w['window_ms']:.2f} ms/device = "
            f"busy {w['busy_ms']:.2f} + "
            f"collective {w['collective_ms']:.2f} + "
            f"transfer {w['transfer_ms']:.2f} + "
            f"host gap {w['host_gap_ms']:.2f} "
            f"(reconciles {w['reconciliation_pct']:.1f}%); "
            f"collectives: "
            + (", ".join(
                f"{op}={v['count_per_unit']:g}/step"
                for op, v in mesh_art["collectives"]["by_op"].items())
               or "none")
            + f" -> {args.mesh_out}",
            file=sys.stderr,
        )
        with open(args.mesh_out, "w") as f:
            json.dump(mesh_art, f, indent=1)
            f.write("\n")

    if args.compare:
        with open(args.compare) as f:
            budget = json.load(f)
        violations = kb.compare_budget(artifact, budget)
        for v in violations:
            print(f"BUDGET VIOLATION: {v}", file=sys.stderr)
        if violations:
            raise SystemExit(1)
        print(f"budget gate holds vs {args.compare}", file=sys.stderr)


if __name__ == "__main__":
    main()
