"""Phase-level wall-clock profile of the north-star config (#5).

Monkeypatch-instruments the TPU engine's main phases so optimization work
can be targeted where the time actually goes:

    gen          synthetic cluster generation (not part of the plan clock)
    ctx_init     AnalyzerContext construction (host mirror)
    upload       device model build + aggregate recompute
    device       compiled search calls (includes device→host transfer)
    host_eval    exact recheck (_HostEvaluator.evaluate)
    host_apply   ctx.apply of committed actions
    finalize     goal violations + diff + stats after search

Usage:
    PYTHONPATH=.:/root/.axon_site python benchmarks/profile_northstar.py \
        [--brokers 10000] [--partitions 1000000] [--budget 0]
"""

from __future__ import annotations

import argparse
import collections
import functools
import json
import time

TIMES: dict = collections.defaultdict(float)
COUNTS: dict = collections.defaultdict(int)


def timed(name, fn):
    @functools.wraps(fn)
    def wrap(*a, **kw):
        t0 = time.perf_counter()
        out = fn(*a, **kw)
        TIMES[name] += time.perf_counter() - t0
        COUNTS[name] += 1
        return out
    return wrap


def main() -> None:
    import logging

    logging.basicConfig(level=logging.INFO)
    from cruise_control_tpu.utils.jit_cache import enable as _jc
    _jc()
    ap = argparse.ArgumentParser()
    ap.add_argument("--brokers", type=int, default=10000)
    ap.add_argument("--partitions", type=int, default=1000000)
    ap.add_argument("--racks", type=int, default=200)
    ap.add_argument("--budget", type=float, default=0.0)
    ap.add_argument("--slack", type=float, default=1.0,
                    help="cohort budget slack factor")
    def _nonneg(v):
        v = int(v)
        if v < 0:
            raise argparse.ArgumentTypeError("rounds must be >= 0")
        return v

    ap.add_argument("--rounds", type=_nonneg, default=0,
                    help="auction rounds (0 = alternates width)")
    ap.add_argument("--dest-cap", type=int, default=1,
                    help="auction winners per destination per step")
    ap.add_argument("--src-cap", type=int, default=1,
                    help="auction winners per source per step")
    ap.add_argument("--diag", action="store_true",
                    help="per-step availability diagnostics (~1 ms/step)")
    ap.add_argument("--cohort-mode", default="budget",
                    choices=("budget", "corrected"))
    ap.add_argument("--stack-tol", type=float, default=1.0,
                    help="corrected-cohort commit-ordering guard "
                         "(>=1 disables)")
    ap.add_argument("--sel-rows", type=int, default=1024,
                    help="post-compaction selection problem size C")
    # defaults mirror TpuSearchConfig so a bare --warm run measures the
    # shipped configuration
    ap.add_argument("--repool", type=int, default=128,
                    help="device pool rebuild cadence (steps)")
    ap.add_argument("--q", type=int, default=4,
                    help="move candidates offered per source broker")
    ap.add_argument("--warm", action="store_true",
                    help="run optimize twice; report the second (compile "
                         "amortized) with phase timers reset")
    ap.add_argument("--artifact", default="",
                    help="write the telemetry phase-profile JSON artifact "
                         "here (schema cc-tpu-phase-profile/1)")
    args = ap.parse_args()

    import cruise_control_tpu.analyzer.tpu_optimizer as T
    from cruise_control_tpu.analyzer import context as C
    from cruise_control_tpu.models.generators import random_cluster
    from cruise_control_tpu.telemetry import profile as tele_profile
    from cruise_control_tpu.telemetry import tracing

    # span-level phases ride along with the monkeypatch timers: the spans
    # are what production emits (bench.py / GET /metrics), the monkeypatch
    # keeps the finer host_eval/host_apply split this script predates
    tracing.configure(enabled=True)

    t0 = time.perf_counter()
    state = random_cluster(
        seed=5, num_brokers=args.brokers, num_racks=args.racks,
        num_partitions=args.partitions,
    )
    TIMES["gen"] = time.perf_counter() - t0

    C.AnalyzerContext.__init__ = timed("ctx_init", C.AnalyzerContext.__init__)
    C.AnalyzerContext.apply = timed("host_apply", C.AnalyzerContext.apply)
    T._HostEvaluator.evaluate = timed("host_eval", T._HostEvaluator.evaluate)
    T.TpuGoalOptimizer._device_model = timed(
        "upload", T.TpuGoalOptimizer._device_model
    )
    step_counts_log = []
    diag_log = []
    orig_fetch = T._fetch_scan_result

    def fetch_wrap(packed, Tn):
        t0 = time.perf_counter()
        out = orig_fetch(packed, Tn)
        TIMES["fetch"] += time.perf_counter() - t0
        COUNTS["fetch"] += 1
        step_counts_log.append(out[4].copy())
        if args.diag and isinstance(out[-1], dict):
            # only meaningful when the scan computed the counters —
            # without --diag the meta rows are zeros, not measurements
            diag_log.append(out[-1])
        return out

    T._fetch_scan_result = fetch_wrap
    T.TpuGoalOptimizer._finalize = timed("finalize", T.TpuGoalOptimizer._finalize)

    orig_scan = T._cached_scan_fn

    @functools.lru_cache(maxsize=64)
    def scan_wrap(cfg, K, D, Tn, mesh=None):
        fn = orig_scan(cfg, K, D, Tn, mesh)

        def run(m, ca, t_cap=None):
            t0 = time.perf_counter()
            packed, m_new = (
                fn(m, ca) if t_cap is None else fn(m, ca, t_cap)
            )
            packed.block_until_ready()
            TIMES["device"] += time.perf_counter() - t0
            COUNTS["device"] += 1
            return packed, m_new
        return run

    T._cached_scan_fn = scan_wrap

    cfg = T.TpuSearchConfig(time_budget_s=args.budget,
                            cohort_budget_slack=args.slack,
                            auction_dest_cap=args.dest_cap,
                            auction_src_cap=args.src_cap,
                            auction_rounds=args.rounds,
                            step_diagnostics=args.diag,
                            cohort_mode=args.cohort_mode,
                            cohort_stack_tol=args.stack_tol,
                            selection_rows=args.sel_rows,
                            repool_steps=args.repool,
                            moves_per_src=args.q)
    opt = T.TpuGoalOptimizer(config=cfg)
    if args.warm:
        opt.optimize(state)
        TIMES.clear()
        COUNTS.clear()
        step_counts_log.clear()
        diag_log.clear()
        tracing.reset()
    t0 = time.perf_counter()
    result = opt.optimize(state)
    total = time.perf_counter() - t0

    out = {
        "total_s": round(total, 2),
        "actions": len(result.actions),
        "violation_score": result.violation_score_after,
        "phases": {k: round(v, 2) for k, v in sorted(TIMES.items())},
        "counts": dict(COUNTS),
        "telemetry_phases": {
            k: round(v, 2) for k, v in tele_profile.phase_breakdown().items()
        },
    }
    if args.artifact:
        tele_profile.write_artifact(args.artifact, extra={
            "fixture": {"brokers": args.brokers,
                        "partitions": args.partitions,
                        "racks": args.racks},
            "total_s": round(total, 2),
            "actions": len(result.actions),
            "violation_score": result.violation_score_after,
        })
    out["phases"]["untracked"] = round(
        total - sum(v for k, v in TIMES.items() if k != "gen"), 2
    )
    if step_counts_log:
        import numpy as np

        # counts[t] for steps that never ran stay 0 — approximate the
        # executed-step count by trimming each call's counts just past its
        # final nonzero index (keeping one trailing zero-commit step, which
        # is a real executed step: the convergence probe)
        executed = []
        for c in step_counts_log:
            nz = np.nonzero(c)[0]
            executed.append(c[: (nz[-1] + 2 if nz.size else 1)])
        ex = np.concatenate(executed)
        out["steps"] = {
            "executed": int(ex.size),
            "actions": int(ex.sum()),
            "mean_commits": round(float(ex.mean()), 1),
            "p50": int(np.percentile(ex, 50)),
            "p90": int(np.percentile(ex, 90)),
            "max": int(ex.max()),
        }
        if diag_log:
            # executed-step availability: how much improving work each
            # snapshot exposed, and which mechanism admitted commits
            n_ex = [len(e) for e in executed]
            imp = np.concatenate([
                d["improving"][:n] for d, n in zip(diag_log, n_ex)
            ])
            coh = np.concatenate([
                d["cohort"][:n] for d, n in zip(diag_log, n_ex)
            ])
            auc = np.concatenate([
                d["auction"][:n] for d, n in zip(diag_log, n_ex)
            ])
            out["availability"] = {
                "improving_mean": round(float(imp.mean()), 1),
                "improving_p50": int(np.percentile(imp, 50)),
                "cohort_mean": round(float(coh.mean()), 1),
                "auction_mean": round(float(auc.mean()), 1),
            }
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
