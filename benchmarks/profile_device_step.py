"""Micro-profile of the device-resident search at north-star shapes.

Times, warm: the full scan call at several T (marginal per-step cost), the
candidate-pool build, one grid rescore, the leadership rescore, and the
auction matcher — so device-side optimization targets the real hot spot.

Usage: PYTHONPATH=.:/root/.axon_site python benchmarks/profile_device_step.py
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import json
import time

import jax
import jax.numpy as jnp


def sync(out):
    # the axon relay's block_until_ready can report ready before remote
    # execution finishes; a concrete scalar fetch is an honest barrier
    import numpy as np

    leaves = [x for x in jax.tree_util.tree_leaves(out)
              if hasattr(x, "block_until_ready")]
    for x in leaves:
        x.block_until_ready()
    if leaves:
        np.asarray(jax.numpy.ravel(leaves[0])[0])


def bench(fn, *args, reps=3):
    sync(fn(*args))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    from cruise_control_tpu.utils.jit_cache import enable as _jc
    _jc()
    ap = argparse.ArgumentParser()
    ap.add_argument("--brokers", type=int, default=10000)
    ap.add_argument("--partitions", type=int, default=1000000)
    args = ap.parse_args()

    import cruise_control_tpu.analyzer.tpu_optimizer as T
    from cruise_control_tpu.analyzer.context import AnalyzerContext
    from cruise_control_tpu.models.generators import random_cluster
    from cruise_control_tpu.ops.grid import move_grid_scores

    state = random_cluster(
        seed=5, num_brokers=args.brokers, num_racks=200,
        num_partitions=args.partitions,
    )
    opt = T.TpuGoalOptimizer()
    cfg = opt.config
    ctx = AnalyzerContext(state)
    m = opt._device_model(ctx)
    ca = opt._constraint_arrays(ctx)
    P, S, B = ctx.num_partitions, ctx.max_rf, ctx.num_brokers
    K, D = opt._pool_sizes(P, S, B)
    cfg = dataclasses.replace(
        cfg, device_batch_per_step=int(min(max(B // 4, 32), 1024))
    )
    res = {"K": K, "D": D, "B": B, "P": P}

    # repeated timing calls reuse one input model, so the scan must not
    # donate its carry in this micro-profile
    nod = dataclasses.replace(cfg, donate_carry=False)
    for Tn in (1, 8, 64):
        fn = T._cached_scan_fn(nod, K, D, Tn)
        res[f"scan_T{Tn}_s"] = round(bench(fn, m, ca), 4)
        print(json.dumps(res), flush=True)

    pools_fn = jax.jit(lambda m, ca: T._build_pools(m, cfg, ca, K, D))
    res["build_pools_s"] = round(bench(pools_fn, m, ca), 4)
    pools = pools_fn(m, ca)

    kp, ks, dest_pool, lp, lsl = pools
    grid_fn = jax.jit(
        lambda m, ca, kp, ks, dp: move_grid_scores(m, cfg, ca, kp, ks, dp)
    )
    res["grid_rescore_s"] = round(bench(grid_fn, m, ca, kp, ks, dest_pool), 4)

    lead_fn = jax.jit(
        lambda m, ca, lp, lsl: T._score_candidates(
            m, cfg, ca, jnp.ones_like(lp), lp, lsl, jnp.zeros_like(lp)
        )
    )
    res["lead_rescore_s"] = round(bench(lead_fn, m, ca, lp, lsl), 4)

    reduced_fn = jax.jit(
        lambda m, ca, pools: T._reduced_candidates(
            m, cfg, ca, K, D, move_grid_scores, pools=pools
        )
    )
    res["reduced_cands_s"] = round(bench(reduced_fn, m, ca, pools), 4)

    print(json.dumps(res, indent=1))


if __name__ == "__main__":
    main()
